// fedml_tpu native message router: a standalone cross-host broker for the
// cross-silo path.
//
// Role: the reference delegates cross-host transport to mpi4py's C library
// (fedml_core/distributed/communication/mpi/) or a prototype gRPC service
// with hardcoded IPs (gRPC/grpc_comm_manager.py:51-56). Here the native
// component is a star-topology frame router: every silo dials the broker
// (works across NAT — silos need no inbound ports), identifies itself with a
// HELLO carrying its rank, then exchanges length-prefixed binary frames
// addressed by destination rank. Payloads are opaque (the Python side uses
// the zero-copy pytree codec in fedml_tpu/comm/serialization.py).
//
// Wire protocol (all integers little-endian):
//   HELLO  (client -> router, once):  u32 magic 'FMLR'  u32 rank
//   HELLO+AUTH (when a shared secret is configured):
//                                     u32 magic 'FMLS'  u32 rank
//                                     u32 token_len     token bytes
//   DATA   (client -> router):        u32 dest_rank     u64 len   payload
//   DATA   (router -> client):        u32 src_rank      u64 len   payload
//
// Security: a router started with a non-empty token rejects any HELLO that
// does not carry the matching token (constant-time compare), closing the
// hole where any host that can reach the port could claim an arbitrary rank
// (including rank 0) and receive the broadcast model or inject updates.
// The token authenticates rank claims only — payloads still cross the wire
// in cleartext, so production deployments must run the broker behind TLS
// termination (stunnel/envoy/nginx stream proxy) or on a trusted network.
//
// Frames to a rank that has not connected yet are buffered (bounded by
// kMaxPendingBytes per rank) and flushed on its HELLO — so the federation
// has no start-order constraints.
//
// Threading: one accept thread + one reader thread per connection. A frame
// is forwarded under the destination's write mutex, so interleaving is
// impossible and backpressure propagates naturally through TCP.
//
// Exposed as a C API (fedml_router_start/stop/...) consumed via ctypes from
// fedml_tpu/native/__init__.py.

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <memory>
#include <mutex>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

constexpr uint32_t kMagic = 0x464d4c52;      // 'FMLR' (legacy, token-less)
constexpr uint32_t kMagicAuth = 0x464d4c53;  // 'FMLS' (token follows)
constexpr size_t kMaxPendingBytes = 1ull << 30;  // 1 GiB buffered per absent rank
constexpr size_t kMaxFrameBytes = 4ull << 30;    // 4 GiB per frame
constexpr uint32_t kMaxTokenLen = 4096;

// Constant-time equality: timing must leak neither matching prefix length
// nor the configured token's length, so iterate over the attacker-supplied
// buffer (whose length the peer already knows), folding the secret in
// cyclically.
bool token_eq(const std::string& a, const char* b, size_t blen) {
  unsigned diff = static_cast<unsigned>(a.size() ^ blen);
  if (a.empty()) return blen == 0;
  for (size_t i = 0; i < blen; ++i) {
    diff |= static_cast<unsigned char>(a[i % a.size()]) ^
            static_cast<unsigned char>(b[i]);
  }
  return diff == 0;
}

bool read_exact(int fd, void* buf, size_t n) {
  auto* p = static_cast<char*>(buf);
  while (n > 0) {
    ssize_t r = ::recv(fd, p, n, 0);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool write_exact(int fd, const void* buf, size_t n) {
  const auto* p = static_cast<const char*>(buf);
  while (n > 0) {
    ssize_t w = ::send(fd, p, n, MSG_NOSIGNAL);
    if (w <= 0) return false;
    p += w;
    n -= static_cast<size_t>(w);
  }
  return true;
}

struct Frame {
  uint32_t src;
  std::vector<char> payload;
};

struct Client {
  int fd = -1;
  std::mutex write_mu;            // serializes router->client frames
  std::atomic<bool> open{false};
};

// fd lifecycle discipline: the winner of open.exchange(false) calls
// ::shutdown() only (unblocking the reader); ::close() is done exclusively
// by the connection's own reader thread, under write_mu, after its read
// loop exits. This guarantees no thread can be mid-recv/mid-send on an fd
// when it is closed, so a reused fd number can never receive another
// connection's bytes.

class Router {
 public:
  Router() = default;

  // Require this shared secret in every HELLO (call before Start).
  // Length-delimited: binary tokens may contain NUL bytes.
  void SetToken(const char* token, size_t len) {
    token_.assign(token ? token : "", token ? len : 0);
  }

  // Returns the bound port (useful with port=0), or -1 on failure.
  int Start(const char* host, int port) {
    listen_fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    if (listen_fd_ < 0) return -1;
    int one = 1;
    ::setsockopt(listen_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(static_cast<uint16_t>(port));
    if (::inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
      ::close(listen_fd_);
      return -1;
    }
    if (::bind(listen_fd_, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) <
            0 ||
        ::listen(listen_fd_, 64) < 0) {
      ::close(listen_fd_);
      return -1;
    }
    socklen_t len = sizeof(addr);
    ::getsockname(listen_fd_, reinterpret_cast<sockaddr*>(&addr), &len);
    port_ = ntohs(addr.sin_port);
    running_.store(true);
    accept_thread_ = std::thread([this] { AcceptLoop(); });
    return port_;
  }

  void Stop() {
    if (!running_.exchange(false)) return;
    ::shutdown(listen_fd_, SHUT_RDWR);
    ::close(listen_fd_);
    // join the acceptor first so no new reader threads can start, then
    // unblock every reader and wait for all of them to drain
    if (accept_thread_.joinable()) accept_thread_.join();
    {
      std::lock_guard<std::mutex> lk(mu_);
      for (auto& [rank, c] : clients_) {
        if (c->open.exchange(false)) ::shutdown(c->fd, SHUT_RDWR);
      }
    }
    std::unique_lock<std::mutex> lk(readers_mu_);
    readers_cv_.wait(lk, [this] { return active_readers_ == 0; });
  }

  int port() const { return port_; }
  uint64_t frames_routed() const { return frames_routed_.load(); }
  uint64_t bytes_routed() const { return bytes_routed_.load(); }
  int connected_ranks() const {
    std::lock_guard<std::mutex> lk(mu_);
    int n = 0;
    for (auto& [rank, c] : clients_) n += c->open.load() ? 1 : 0;
    return n;
  }

  ~Router() { Stop(); }

 private:
  void AcceptLoop() {
    while (running_.load()) {
      int fd = ::accept(listen_fd_, nullptr, nullptr);
      if (fd < 0) break;  // listener closed by Stop()
      int one = 1;
      ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      {
        std::lock_guard<std::mutex> lk(readers_mu_);
        ++active_readers_;
      }
      // detached: reconnecting silos would otherwise accumulate one
      // never-joined std::thread per connection until Stop()
      std::thread([this, fd] {
        ServeConnection(fd);
        std::lock_guard<std::mutex> lk(readers_mu_);
        if (--active_readers_ == 0) readers_cv_.notify_all();
      }).detach();
    }
  }

  void ServeConnection(int fd) {
    // HELLO must arrive promptly: an untracked half-open connection would
    // otherwise block Stop() on this thread's join forever
    timeval hello_timeout{10, 0};
    ::setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &hello_timeout,
                 sizeof(hello_timeout));
    uint32_t magic = 0, rank = 0;
    if (!read_exact(fd, &magic, 4) ||
        (magic != kMagic && magic != kMagicAuth) ||
        !read_exact(fd, &rank, 4)) {
      ::close(fd);
      return;
    }
    if (magic == kMagicAuth) {
      uint32_t tlen = 0;
      if (!read_exact(fd, &tlen, 4) || tlen > kMaxTokenLen) {
        ::close(fd);
        return;
      }
      std::vector<char> tok(tlen);
      if (tlen > 0 && !read_exact(fd, tok.data(), tlen)) {
        ::close(fd);
        return;
      }
      if (!token_eq(token_, tok.data(), tok.size())) {
        ::close(fd);
        return;
      }
    } else if (!token_.empty()) {
      // token required but the peer sent a legacy HELLO: reject
      ::close(fd);
      return;
    }
    timeval no_timeout{0, 0};
    ::setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &no_timeout,
                 sizeof(no_timeout));
    std::shared_ptr<Client> self;
    std::deque<Frame> undelivered;
    {
      // registration and backlog flush happen with write_mu held, so a
      // frame routed concurrently by a sender's reader (which sees
      // open==true the instant it is stored) cannot overtake the buffered
      // frames — per-sender FIFO is preserved across the reconnect
      std::unique_lock<std::mutex> lk(mu_);
      auto& slot = clients_[rank];
      if (!slot) slot = std::make_shared<Client>();
      if (slot->open.load()) {  // duplicate rank: refuse the newcomer
        lk.unlock();
        ::close(fd);
        return;
      }
      self = slot;
      std::lock_guard<std::mutex> wlk(self->write_mu);
      self->fd = fd;
      self->open.store(true);
      std::deque<Frame> backlog;
      auto it = pending_.find(rank);
      if (it != pending_.end()) {
        backlog.swap(it->second.frames);
        pending_.erase(it);
      }
      lk.unlock();
      while (!backlog.empty()) {
        if (!DeliverLocked(*self, backlog.front().src,
                           backlog.front().payload)) {
          undelivered.swap(backlog);  // connection died during the flush
          break;
        }
        backlog.pop_front();
      }
    }
    if (!undelivered.empty()) {
      // put what the dead connection never received back at the head of
      // the queue for the next reconnect (write_mu released: mu_ must
      // never be acquired while holding a write_mu)
      std::lock_guard<std::mutex> lk(mu_);
      auto& q = pending_[rank];
      for (auto it = undelivered.rbegin(); it != undelivered.rend(); ++it) {
        q.bytes += it->payload.size();
        q.frames.push_front(std::move(*it));
      }
    }

    // read loop: route every inbound frame
    for (;;) {
      uint32_t dest = 0;
      uint64_t len = 0;
      if (!read_exact(fd, &dest, 4) || !read_exact(fd, &len, 8) ||
          len > kMaxFrameBytes) {
        break;
      }
      std::vector<char> payload;
      try {
        payload.resize(len);
      } catch (const std::bad_alloc&) {
        break;  // oversized claim: drop this connection, not the broker
      }
      if (len > 0 && !read_exact(fd, payload.data(), len)) break;
      if (!Route(rank, dest, std::move(payload))) break;
    }
    self->open.exchange(false);
    ::shutdown(fd, SHUT_RDWR);
    // serialize against any in-flight Deliver before the fd number can be
    // reused by a future accept
    std::lock_guard<std::mutex> wlk(self->write_mu);
    ::close(fd);
  }

  // Returns false when the frame had to be dropped (pending overflow) —
  // the caller then drops the sender's connection so the failure is
  // visible instead of the federation hanging on a silently lost message.
  // A frame whose destination disconnects mid-delivery is requeued into
  // pending_ (the destination's inbound stream restarts fresh on
  // reconnect, so redelivering the whole frame is safe).
  bool Route(uint32_t src, uint32_t dest, std::vector<char> payload) {
    for (;;) {
      std::shared_ptr<Client> target;
      {
        std::lock_guard<std::mutex> lk(mu_);
        auto it = clients_.find(dest);
        if (it != clients_.end() && it->second->open.load()) {
          target = it->second;
        } else {
          auto& q = pending_[dest];
          if (q.bytes + payload.size() > kMaxPendingBytes) return false;
          q.bytes += payload.size();
          q.frames.push_back(Frame{src, std::move(payload)});
          return true;
        }
      }
      std::lock_guard<std::mutex> lk(target->write_mu);
      if (DeliverLocked(*target, src, payload)) return true;
      // destination died mid-flight: loop — it is now closed (requeue into
      // pending_) or already reconnected (retry delivery)
    }
  }

  // Caller must hold c.write_mu. Returns false if the frame was NOT
  // delivered (connection closed or write failed).
  bool DeliverLocked(Client& c, uint32_t src,
                     const std::vector<char>& payload) {
    uint64_t len = payload.size();
    if (!c.open.load()) return false;
    if (!write_exact(c.fd, &src, 4) || !write_exact(c.fd, &len, 8) ||
        (len > 0 && !write_exact(c.fd, payload.data(), len))) {
      if (c.open.exchange(false)) ::shutdown(c.fd, SHUT_RDWR);
      return false;
    }
    frames_routed_.fetch_add(1);
    bytes_routed_.fetch_add(len);
    return true;
  }

  struct PendingQueue {
    size_t bytes = 0;
    std::deque<Frame> frames;
  };

  std::string token_;  // empty = open (legacy HELLO accepted)
  int listen_fd_ = -1;
  int port_ = -1;
  std::atomic<bool> running_{false};
  std::thread accept_thread_;
  mutable std::mutex mu_;  // guards clients_ and pending_
  std::unordered_map<uint32_t, std::shared_ptr<Client>> clients_;
  std::unordered_map<uint32_t, PendingQueue> pending_;
  std::mutex readers_mu_;  // with readers_cv_: Stop() waits for readers
  std::condition_variable readers_cv_;
  int active_readers_ = 0;
  std::atomic<uint64_t> frames_routed_{0};
  std::atomic<uint64_t> bytes_routed_{0};
};

}  // namespace

extern "C" {

// token may be null/zero-length for an open (unauthenticated) router; a
// non-empty token makes every HELLO carry-and-match it ('FMLS' form).
// token_len is explicit so binary secrets with NUL bytes survive the FFI.
void* fedml_router_start(const char* host, int port, const char* token,
                         int token_len, int* out_port) {
  auto* r = new Router();
  r->SetToken(token, token_len > 0 ? static_cast<size_t>(token_len) : 0);
  int bound = r->Start(host, port);
  if (bound < 0) {
    delete r;
    return nullptr;
  }
  if (out_port) *out_port = bound;
  return r;
}

void fedml_router_stop(void* handle) {
  auto* r = static_cast<Router*>(handle);
  if (!r) return;
  r->Stop();
  delete r;
}

int fedml_router_port(void* handle) {
  return handle ? static_cast<Router*>(handle)->port() : -1;
}

unsigned long long fedml_router_frames_routed(void* handle) {
  return handle ? static_cast<Router*>(handle)->frames_routed() : 0;
}

unsigned long long fedml_router_bytes_routed(void* handle) {
  return handle ? static_cast<Router*>(handle)->bytes_routed() : 0;
}

int fedml_router_connected_ranks(void* handle) {
  return handle ? static_cast<Router*>(handle)->connected_ranks() : 0;
}

}  // extern "C"
