#!/usr/bin/env bash
# The reference's golden invariant as a standalone gate
# (CI-script-fedavg.sh:44-49): full participation + full batch + 1 local
# epoch => FedAvg == centralized training accuracy. Runs the pytest
# expression that asserts it to three decimals in f32.
set -euo pipefail
cd "$(dirname "$0")/.."
exec python -m pytest -q \
  "tests/test_fedavg.py::TestCentralizedEquivalence" "$@"
