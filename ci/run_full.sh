#!/usr/bin/env bash
# CI full lane (nightly): the whole pyramid incl. compile-heavy model-zoo,
# NAS search, multihost rendezvous, SIGKILL-resume. ~40 min on a
# laptop-class box.
set -euo pipefail
cd "$(dirname "$0")/.."
exec python -m pytest tests/ -q "$@"
