#!/usr/bin/env bash
# CI static lane: fedml_tpu.analysis (AST lint FT001-FT006 + jaxpr audit
# of the registered hot entry points) over fedml_tpu/ and tests/.
# Exit non-zero on any finding that is not fixed, pragma'd
# (# ft: allow[FTxxx]) or baselined in ci/analysis_baseline.json.
# The JSON report lands in runs/static_analysis.json as a CI artifact.
# Extra args pass through (e.g. --no-audit for a sub-second lint-only
# pre-commit hook).
set -euo pipefail
cd "$(dirname "$0")/.."
mkdir -p runs
exec env JAX_PLATFORMS=cpu \
    XLA_FLAGS="${XLA_FLAGS:---xla_force_host_platform_device_count=8}" \
    python -m fedml_tpu.analysis \
    --baseline ci/analysis_baseline.json \
    --output runs/static_analysis.json \
    "$@"
