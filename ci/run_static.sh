#!/usr/bin/env bash
# CI static lane: fedml_tpu.analysis over fedml_tpu/ and tests/ —
# AST lint (FT001-FT015 incl. the determinism rules, plus the
# resource-lifecycle rules FT020-FT024) + unused-pragma strictness
# (FT012) + the whole-program protocol conformance pass (FT2xx,
# drift-checked against ci/protocol_graph.json) + round-shape
# conformance over the algorithms/ driver zoo (FT30x, drift-checked
# against ci/round_engine_map.json; accept with --write-round-map) +
# the shutdown-graph extraction (FT025, drift-checked against
# ci/shutdown_graph.json; accept with --write-shutdown-graph) +
# flag/env conformance (FT016, vs the README flag/env tables) + the
# jaxpr/collective audit of registered hot entry points (FT10x,
# drift-checked against ci/collective_baseline.json).
# Exit non-zero on any finding that is not fixed, pragma'd
# (# ft: allow[FTxxx]) or baselined in ci/analysis_baseline.json.
# CI artifacts: runs/static_analysis.json (report),
# runs/protocol_graph.json (sender->handler graph),
# runs/round_engine_map.json (the round-engine parity oracle),
# runs/shutdown_graph.json (the worker/resource teardown map).
#
# Fast pre-commit lane (sub-second, no jax import):
#   ci/run_static.sh --changed-only            # lint files touched vs HEAD
#   ci/run_static.sh --changed-only origin/main
# (--changed-only implies --no-audit --no-protocol --no-roundshape
# --no-flags --no-lifecycle inside the CLI — every whole-program pass
# skips; the per-file FT020-FT024 rules still run there, kept cheap by
# their textual pre-gates: a changed file without "Thread("/"socket"/
# "Lock"/"Queue"-class tokens costs a substring scan, no AST walk.)
#
# Under GitHub Actions ($GITHUB_ACTIONS set) findings are emitted as
# ::error file=...,line=...:: annotations.
# Extra args pass through (e.g. --no-audit for lint+protocol only).
set -euo pipefail
cd "$(dirname "$0")/.."
mkdir -p runs
FORMAT=()
if [ -n "${GITHUB_ACTIONS:-}" ]; then
    FORMAT=(--format github)
fi
exec env JAX_PLATFORMS=cpu \
    XLA_FLAGS="${XLA_FLAGS:---xla_force_host_platform_device_count=8}" \
    python -m fedml_tpu.analysis \
    --baseline ci/analysis_baseline.json \
    --strict-pragmas \
    --output runs/static_analysis.json \
    ${FORMAT[@]+"${FORMAT[@]}"} \
    "$@"
