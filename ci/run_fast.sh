#!/usr/bin/env bash
# CI fast lane (the reference's per-PR Travis role, CI-script-fedavg.sh):
# static analysis (analysis CLI: AST lint + jaxpr audit, ~25 s), then a
# 100k-client population-virtualization smoke (seconds — FedAvg rounds
# through the tiered client-state store; the 1M leg lives in the slow
# lane + the population_scale bench stage), then the server-failover
# smoke (~25 s — a real TCP server subprocess SIGKILLed mid-schedule,
# restarted, and required to finish with cp_restores >= 1 and a
# ledger matching the unkilled reference), then unit + integration
# tests on 8 virtual CPU devices, ~7 min.
set -euo pipefail
cd "$(dirname "$0")/.."
./ci/run_static.sh
JAX_PLATFORMS=cpu python -m fedml_tpu.state.population \
    --population 100000 --rounds 2 --cohort 10
JAX_PLATFORMS=cpu python -m fedml_tpu.control.failover_harness --smoke
# slowest-20 artifact (tests/conftest.py sessionfinish hook): fast-lane
# time creep becomes a diffable runs/ number instead of a README anecdote
export FEDML_TPU_TEST_DURATIONS="runs/test_durations.json"
exec python -m pytest tests/ -q -m "not slow" "$@"
