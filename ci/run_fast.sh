#!/usr/bin/env bash
# CI fast lane (the reference's per-PR Travis role, CI-script-fedavg.sh):
# unit + integration tests on 8 virtual CPU devices, ~6 min.
set -euo pipefail
cd "$(dirname "$0")/.."
exec python -m pytest tests/ -q -m "not slow" "$@"
