#!/usr/bin/env bash
# CI fast lane (the reference's per-PR Travis role, CI-script-fedavg.sh):
# static analysis (analysis CLI: AST lint + jaxpr audit, ~25 s), then a
# 100k-client population-virtualization smoke (seconds — FedAvg rounds
# through the tiered client-state store; the 1M leg lives in the slow
# lane + the population_scale bench stage), then the server-failover
# smoke (~25 s — a real TCP server subprocess SIGKILLed mid-schedule,
# restarted, and required to finish with cp_restores >= 1 and a ledger
# matching the unkilled reference) now recording a flight log that
# `obs merge --ledger` must rebuild cleanly (a real two-epoch SIGKILL
# log, artifact under runs/obs_smoke/), then unit + integration tests
# on 8 virtual CPU devices, ~7 min, followed by the SOFT-FAIL trend
# lane: the session's trend-ledger rows (bench stages + the pytest
# tests/sec row this run just appended) are checked against their
# trailing medians — regressions WARN while the trajectory builds;
# flip to a hard gate once runs/trends.jsonl has history.
set -euo pipefail
cd "$(dirname "$0")/.."
./ci/run_static.sh
JAX_PLATFORMS=cpu python -m fedml_tpu.state.population \
    --population 100000 --rounds 2 --cohort 10
rm -rf runs/obs_smoke && mkdir -p runs/obs_smoke
JAX_PLATFORMS=cpu python -m fedml_tpu.control.failover_harness --smoke \
    --ckpt_dir runs/obs_smoke --obs_dir runs/obs_smoke/flight
# same SIGKILL smoke under the LEGACY inline checkpointer: the default
# leg above exercises the async writer (coalescing slot, writer-thread
# fsync, restore-on-older-boundary + ledger replay); this leg pins
# --checkpoint_sync to the old synchronous semantics so both durability
# modes keep the bit-exact failover contract
rm -rf runs/obs_smoke_sync && mkdir -p runs/obs_smoke_sync
JAX_PLATFORMS=cpu python -m fedml_tpu.control.failover_harness --smoke \
    --checkpoint_sync --ckpt_dir runs/obs_smoke_sync
JAX_PLATFORMS=cpu python -m fedml_tpu.obs merge runs/obs_smoke/flight \
    --ledger runs/obs_smoke/killed/ledger.jsonl \
    --output runs/obs_smoke/merged.json
# multi-job tenancy smoke (fedml_tpu/sched): two federation jobs over
# ONE shared fabric + device, the victim's server SIGKILLed
# mid-schedule and respawned — exits non-zero unless the survivor's
# ledger AND final model are bit-identical to its solo leg, the victim
# recovered via its own job_<id>/ checkpoint (cp_restores >= 1), and
# `obs report` renders one per-tenant summary from the shared obs dir
rm -rf runs/sched_smoke
JAX_PLATFORMS=cpu python -m fedml_tpu.sched smoke --root runs/sched_smoke
# WAN churn smoke (fedml_tpu/wan, ~20 s): a small federation over TCP
# through a diurnal trough + flap burst — exits non-zero unless the
# FULL schedule completed (churn degrades, never stalls), >= 1 silo was
# deadline-evicted AND >= 1 rejoined through the trace-gated JOIN path,
# every sampled cohort member was trace-available, and re-running the
# same trace seed produced a bit-identical round/cohort ledger
JAX_PLATFORMS=cpu python -m fedml_tpu.wan --smoke
# round-hot-path fan-out smoke (fedml_tpu/comm, ~15 s): a real-TCP
# broadcast against a peer that stalls its reads (kernel backpressure)
# plus a 4-silo federation with a chaos-delayed silo — exits non-zero
# unless the round-open broadcast returns in a fraction of the stall,
# fast peers drain while the slow peer is still wedged, the payload
# was encoded exactly once, and the chaos run's ledger + final model
# are bit-identical to the fault-free reference
JAX_PLATFORMS=cpu python -m fedml_tpu.comm.fanout_smoke
# federated-serving smoke (fedml_tpu/serve, ~10 s): train a small
# federation WITH the TCP/JSON inference endpoint attached, drive 50
# closed-loop requests, and exit non-zero unless at least one hot swap
# landed, ZERO requests were shed, and the SLO report carries measured
# latency quantiles + the served round
JAX_PLATFORMS=cpu python -m fedml_tpu.serve --smoke
# named-mesh smoke (fedml_tpu/parallel/mesh, ~5 s, <= 20 s budget): a
# real 2-device data-mesh federation with the flight recorder ON — 3
# host rounds + one fused 2-round block through the named-mesh scan,
# the mesh entry points' collective signatures audited against
# ci/collective_baseline.json, and the flight log rebuilt by
# `obs merge --ledger` at rc 0 (artifact under runs/mesh_smoke/)
env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=2" \
    python -m fedml_tpu.parallel.mesh --smoke --force-host \
    --out runs/mesh_smoke
# slowest-20 artifact (tests/conftest.py sessionfinish hook): fast-lane
# time creep becomes a diffable runs/ number instead of a README
# anecdote — AND a trend-ledger row, so creep regresses like a bench
export FEDML_TPU_TEST_DURATIONS="runs/test_durations.json"
export FEDML_TPU_TREND_LEDGER="runs/trends.jsonl"
rc=0
python -m pytest tests/ -q -m "not slow" "$@" || rc=$?
JAX_PLATFORMS=cpu python -m fedml_tpu.obs trend runs/trends.jsonl \
    --check-latest \
    || echo "WARNING: performance trend regression (soft-fail lane;" \
            "see runs/trends.jsonl)" >&2
exit "$rc"
