#!/usr/bin/env bash
# CI fast lane (the reference's per-PR Travis role, CI-script-fedavg.sh):
# static analysis (analysis CLI: AST lint + jaxpr audit, ~25 s), then
# unit + integration tests on 8 virtual CPU devices, ~7 min.
set -euo pipefail
cd "$(dirname "$0")/.."
./ci/run_static.sh
exec python -m pytest tests/ -q -m "not slow" "$@"
