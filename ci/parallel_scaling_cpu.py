"""CPU scaling curve for the parallelism layer — correctness/overhead
evidence on the 8-virtual-device mesh.

This host has ONE physical core, so virtual-device sharding cannot show a
wall-clock speedup; what this curve pins is that the sharded federated
round programs (sequence-parallel ring attention, Megatron TP) stay
numerically healthy and within a constant-factor overhead of the unsharded
program as the model axis grows 1 -> 2 -> 4 -> 8. On a real slice the same
programs ride ICI (tests + dryrun_multichip validate placement).

Writes runs/parallel_scaling_cpu.json.
Run: env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  python ci/parallel_scaling_cpu.py
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
from jax.sharding import Mesh  # noqa: E402

from fedml_tpu.models.transformer import TransformerLM  # noqa: E402
from fedml_tpu.parallel.sequence import make_seq_federated_round  # noqa: E402
from fedml_tpu.parallel.tensor import make_tp_federated_round  # noqa: E402
from fedml_tpu.trainer.functional import TrainConfig  # noqa: E402


def measure(kind: str, n_model: int, S: int = 128) -> float:
    devs = jax.devices()
    n_cl = len(devs) // n_model
    P = n_cl
    vocab, width, heads = 128, 32, 2
    n_pad, bsz, steps = 2, 2, 3
    cfg = TrainConfig(epochs=1, batch_size=bsz, lr=0.1)
    rng = np.random.RandomState(0)
    mesh = Mesh(np.asarray(devs[:n_cl * n_model]).reshape(n_cl, n_model),
                ("clients", kind))
    lm = TransformerLM(vocab_size=vocab, width=width, depth=1,
                       num_heads=heads, max_len=S)
    x = rng.randint(0, vocab, (P, n_pad, S)).astype(np.int32)
    y = np.roll(x, -1, axis=-1).astype(np.int32)
    mask = np.ones((P, n_pad), np.float32)
    weights = np.full((P,), float(n_pad), np.float32)
    keys = jax.random.split(jax.random.key(0), P)
    variables = lm.init(jax.random.key(1), jnp.asarray(x[0, :1]),
                        train=False)
    if kind == "seq":
        round_fn = make_seq_federated_round(lm, cfg, mesh)
    else:
        round_fn, shard_params = make_tp_federated_round(lm, "nwp", cfg,
                                                         mesh)
        variables = shard_params(variables)
    args = (jnp.asarray(x), jnp.asarray(y), jnp.asarray(mask), keys,
            jnp.asarray(weights))
    v, stats = round_fn(variables, *args)
    jax.block_until_ready(v)
    assert np.isfinite(float(stats["loss_sum"]))
    t0 = time.perf_counter()
    for _ in range(steps):
        v, _ = round_fn(v, *args)
    jax.block_until_ready(v)
    return round(steps * P * n_pad * S / (time.perf_counter() - t0), 1)


def main():
    out = {"host": "single-core CPU, 8 virtual devices",
           "note": "overhead curve, not a speedup claim (1 physical core)",
           "seq": {}, "tp": {}}
    for kind in ("seq", "tp"):
        for n_model in (1, 2, 4, 8):
            tps = measure(kind, n_model)
            out[kind][str(n_model)] = tps
            print(f"{kind} x{n_model}: {tps} tokens/s", flush=True)
    os.makedirs("runs", exist_ok=True)
    with open(os.path.join("runs", "parallel_scaling_cpu.json"), "w") as f:
        json.dump(out, f, indent=2)
    print("wrote runs/parallel_scaling_cpu.json")


if __name__ == "__main__":
    main()
