#!/bin/bash
# Session-2 follow-up probe loop: when the tunnel comes back, capture the
# two remaining chip items, then exit. Safe to re-run; each step is gated
# on its artifact. Timeline appended to runs/tpu_probe_r5b.log.
cd /root/repo || exit 1
LOG=runs/tpu_probe_r5b.log

probe() {
  timeout 75 python3 -c "
import jax, jax.numpy as jnp
assert jax.default_backend() == 'tpu'
print(float(jnp.ones(8).sum()))" >/dev/null 2>&1
}

for i in $(seq 1 200); do
  if probe; then
    echo "$(date -u +%FT%TZ) probe LIVE (iter $i)" >> "$LOG"

    # 1) resnet bench stage with the fixed cost probe (real flops/MFU)
    if python3 -c "
import json,sys
d=json.load(open('runs/bench_partial.json'))
r=d.get('resnet18_gn_fedcifar100',{})
sys.exit(0 if r.get('mfu') is not None else 1)"; then
      echo "$(date -u +%FT%TZ) resnet row already has mfu" >> "$LOG"
    else
      FEDML_BENCH_TOTAL_TIMEOUT_S=600 timeout 700 \
        python3 bench.py --stages=resnet --resume-partial \
        >> runs/bench_r5_live.log 2>&1
      echo "$(date -u +%FT%TZ) resnet re-capture rc=$?" >> "$LOG"
    fi

    # 2) cross-silo bf16 perf datum (3 rounds; also validates the
    #    numpy-tree warmup fix on chip — round 0 should now be fast)
    if [ ! -f runs/cross_silo_resnet56_chip_bf16/metrics.jsonl ]; then
      [ -d "$HOME/.cache/fedml_tpu_gen/cifar10_synth" ] || \
        python3 runs/gen_cifar10_synth.py >> "$LOG" 2>&1
      timeout 2400 python3 -m fedml_tpu.experiments.fed_launch \
        --algo fedavg_cross_silo --dataset cifar10 \
        --data_dir "$HOME/.cache/fedml_tpu_gen/cifar10_synth" \
        --model resnet56 --partition_method hetero --partition_alpha 0.5 \
        --client_num_in_total 10 --client_num_per_round 10 \
        --comm_round 3 --epochs 20 --batch_size 64 --lr 0.01 \
        --compute_dtype bfloat16 \
        --run_dir runs/cross_silo_resnet56_chip_bf16 \
        >> runs/cross_silo_resnet56_chip_bf16.log 2>&1
      echo "$(date -u +%FT%TZ) cross-silo bf16 rc=$?" >> "$LOG"
    fi
    echo "$(date -u +%FT%TZ) capture sequence done; loop exits" >> "$LOG"
    exit 0
  fi
  echo "$(date -u +%FT%TZ) probe dead (iter $i)" >> "$LOG"
  sleep 240
done
