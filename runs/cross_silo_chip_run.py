"""Launch the cross-silo ResNet-56 chip anchor with on-demand stack dumps:
``kill -USR1 <pid>`` appends every thread's Python stack to stderr, so a
tunnel wedge can be located without killing the run."""
import faulthandler
import os
import signal
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

faulthandler.enable()  # native crashes (SIGSEGV in the tunnel client) too
faulthandler.register(signal.SIGUSR1, all_threads=True)
faulthandler.dump_traceback_later(1200, repeat=True)  # heartbeat stacks

from fedml_tpu.experiments import fed_launch  # noqa: E402

sys.exit(fed_launch.main([
    "--algo", "fedavg_cross_silo", "--dataset", "cifar10",
    "--data_dir", sys.argv[1],
    "--model", "resnet56", "--partition_method", "hetero",
    "--partition_alpha", "0.5",
    "--client_num_in_total", "10", "--client_num_per_round", "10",
    "--comm_round", "100", "--epochs", "20", "--batch_size", "64",
    "--lr", "0.01", "--run_dir", "runs/cross_silo_resnet56_chip",
]) and 0)
