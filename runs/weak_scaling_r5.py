"""Weak-scaling measurement over real multi-process rendezvous.

Runs the FedAvg SPMD round at P = 1/2/4/8 processes x 4 virtual CPU
devices each (per-host work FIXED at 4 clients — weak scaling), through
jax.distributed's actual coordinator handshake and DCN collectives —
the shape `mpirun -np N` exercises in the reference
(run_fedavg_distributed_pytorch.sh:19-22).

On this 1-core host all P processes time-share one core, so absolute
rounds/s falls ~1/P by construction; the quantity of interest is the
PROTOCOL overhead (rendezvous + cross-process collective cost) layered
on top of that compute dilution, which feeds the BASELINE.md v5e-256
projection. Writes runs/weak_scaling_r5.json.

Usage: python runs/weak_scaling_r5.py [--procs 1,2,4,8]
"""

import argparse
import json
import os
import socket
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "multihost_worker.py")


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def run_p(num_procs: int, timeout_s: float = 600.0):
    coordinator = f"127.0.0.1:{free_port()}"
    t0 = time.time()
    env = {**os.environ,
           "PYTHONPATH": REPO + os.pathsep + os.environ.get("PYTHONPATH",
                                                            "")}
    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, coordinator, str(num_procs), str(pid),
             "bench"],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            cwd=REPO, env=env)
        for pid in range(num_procs)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout_s)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        return {"procs": num_procs, "error": f"timeout {timeout_s}s"}
    wall = time.time() - t0
    for out, p in zip(outs, procs):
        if p.returncode != 0:
            return {"procs": num_procs, "error": out[-800:]}
    line = next(l for l in outs[0].splitlines() if l.startswith("BENCH_OK"))
    _, rps, ms = line.split()
    return {"procs": num_procs, "global_devices": 4 * num_procs,
            "clients_total": 4 * num_procs, "clients_per_host": 4,
            "rounds_per_sec": float(rps), "ms_per_round": float(ms),
            "wall_s_incl_rendezvous": round(wall, 1)}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--procs", default="1,2,4,8")
    args = ap.parse_args()
    rows = []
    for p in (int(x) for x in args.procs.split(",")):
        row = run_p(p)
        rows.append(row)
        print(json.dumps(row), flush=True)
    out = {
        "host": "1-core CPU, 4 virtual devices per process",
        "note": ("weak scaling: 4 clients/host fixed; P processes "
                 "time-share ONE core, so rounds/s ~ 1/P is the compute "
                 "dilution floor; deviation below 1/P is protocol "
                 "overhead (rendezvous amortizes, per-round DCN "
                 "collective cost is the steady-state term)"),
        "rows": rows,
    }
    with open(os.path.join(REPO, "runs", "weak_scaling_r5.json"), "w") as f:
        json.dump(out, f, indent=2)
    print("wrote runs/weak_scaling_r5.json", flush=True)


if __name__ == "__main__":
    main()
