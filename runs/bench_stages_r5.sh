#!/bin/bash
# Bench stages to (re)capture on a live window, in value order. Called
# fresh by probe_loop_r5.sh each window, so this file can be edited while
# the loop sleeps (bash reads the loop script incrementally; this one is
# re-read per invocation). $1 = step index to run (1..N); rc passthrough.
cd /root/repo || exit 1

bench_step() {
  FEDML_BENCH_TOTAL_TIMEOUT_S=900 timeout 1000 \
    python3 bench.py "--stages=$1" --resume-partial \
    >> runs/bench_r5_live.log 2>&1
}

case "$1" in
  1) bench_step headline,bf16,fused_headline,fused,fused_device ;;
  2) bench_step resnet,flash,powerlaw ;;
  3) bench_step axes,tta_mnist,tta ;;
  *) exit 0 ;;
esac
