#!/bin/bash
# Bench stages to (re)capture on a live window, in value order. Called
# fresh by probe_loop_r5.sh each window, so this file can be edited while
# the loop sleeps (bash reads the loop script incrementally; this one is
# re-read per invocation). $1 = step index to run (1..N); rc passthrough.
# Each step is gated on its partial keys being tpu-captured already —
# re-running a captured stage would burn window budget and, on a wedge,
# overwrite good chip rows with error rows.
cd /root/repo || exit 1

step_done() {  # $@ = partial keys; exit 0 when all tpu-tagged
  python3 - "$@" <<'EOF'
import json, sys
try:
    d = json.load(open("runs/bench_partial.json"))
except Exception:
    sys.exit(1)
ok = all(str(d.get(k, {}).get("host", "")).startswith("tpu")
         and "error" not in d.get(k, {}) and "skipped" not in d.get(k, {})
         for k in sys.argv[1:])
sys.exit(0 if ok else 1)
EOF
}

bench_step() {
  FEDML_BENCH_TOTAL_TIMEOUT_S=900 timeout 1000 \
    python3 bench.py "--stages=$1" --resume-partial \
    >> runs/bench_r5_live.log 2>&1
}

case "$1" in
  1) step_done fedavg_femnist_cnn fedavg_femnist_cnn_bf16 \
               fedavg_femnist_cnn_fused fedavg_fused_rounds \
               fedavg_fused_device_sampling \
       || bench_step headline,bf16,fused_headline,fused,fused_device ;;
  2) step_done resnet18_gn_fedcifar100 transformer_flash_s2048 \
               fedavg_powerlaw_1000 \
       || bench_step resnet,flash,powerlaw ;;
  3) step_done federated_parallel_axes time_to_target_mnist_lr \
               time_to_target_acc \
       || bench_step axes,tta_mnist,tta ;;
  *) exit 0 ;;
esac
