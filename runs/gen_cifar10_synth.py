"""Regenerate the synthetic CIFAR-10-format corpus the cross-silo anchor
protocol trains on (``~/.cache/fedml_tpu_gen/cifar10_synth``).

The corpus is LEARNABLE (class prototypes + pixel noise, the same recipe
as fedml_tpu/data/flagship_gen.py) and written in the standard CIFAR-10
python-pickle layout that ``fedml_tpu.data.cifar._read_cifar10_dir``
reads (``data_batch_*`` with ``b"data"`` rows of 3072 uint8 + ``b"labels"``,
plus ``test_batch``) — the reference loader's format
(fedml_api/data_preprocessing/cifar10/data_loader.py). Deterministic
(seed 0), so a wiped cache regenerates bit-identically.
"""
import os
import pickle

import numpy as np

OUT = os.path.join(os.path.expanduser("~"), ".cache", "fedml_tpu_gen",
                   "cifar10_synth")
N_TRAIN, N_TEST, CLASSES = 50000, 10000, 10
NOISE = 64.0  # uint8-scale pixel noise around each class prototype


def _prototypes(rng):
    # smooth per-class patterns: low-frequency sinusoid mixtures so a
    # conv net has real spatial structure to learn, not lookup noise
    yy, xx = np.mgrid[0:32, 0:32].astype(np.float32) / 32.0
    protos = []
    for c in range(CLASSES):
        chans = []
        for _ in range(3):
            f1, f2, p1, p2 = rng.uniform(0.5, 3.0, 4)
            img = (np.sin(2 * np.pi * (f1 * xx + p1))
                   + np.cos(2 * np.pi * (f2 * yy + p2)))
            chans.append(img)
        img = np.stack(chans, -1)
        img = (img - img.min()) / (np.ptp(img) + 1e-9)
        protos.append(img * 200.0 + 27.0)
    return np.stack(protos)  # [C, 32, 32, 3]


def _split(rng, protos, n):
    y = rng.randint(0, CLASSES, n)
    x = protos[y] + rng.normal(0.0, NOISE, (n, 32, 32, 3))
    x = np.clip(x, 0, 255).astype(np.uint8)
    # CIFAR pickle layout: rows are R-plane, G-plane, B-plane flattened
    rows = x.transpose(0, 3, 1, 2).reshape(n, 3072)
    return rows, y.astype(int).tolist()


def main():
    rng = np.random.RandomState(0)
    protos = _prototypes(rng)
    os.makedirs(OUT, exist_ok=True)
    per = N_TRAIN // 5
    for b in range(1, 6):
        rows, labels = _split(rng, protos, per)
        with open(os.path.join(OUT, f"data_batch_{b}"), "wb") as f:
            pickle.dump({b"data": rows, b"labels": labels}, f)
    rows, labels = _split(rng, protos, N_TEST)
    with open(os.path.join(OUT, "test_batch"), "wb") as f:
        pickle.dump({b"data": rows, b"labels": labels}, f)
    print(f"wrote {N_TRAIN} train + {N_TEST} test to {OUT}")


if __name__ == "__main__":
    main()
