#!/bin/bash
# Round-5 TPU probe cadence. VERDICT r4 #1/#2: the flagship TTA anchor
# curves are THE highest-value chip artifacts and are captured FIRST in
# any live window, before any bench stage (round 4 lost both windows to
# bench stages ordered ahead of the flagship step).
#
# Window capture order:
#   1. FEMNIST 1500-round TTA curve on chip  (84.9% calibrated ceiling)
#   2. fed-CIFAR100 4000-round TTA on chip   (44.7% ceiling)
#   3. bench stage groups (runs/bench_stages_r5.sh — editable while the
#      loop sleeps; the loop script itself must NOT be edited while live)
#   4. MNIST-LR chip flagship, Shakespeare chip flagship (if registered)
# Every step persists incrementally (flagship_scale history flusher,
# bench_partial.json) and is attempted independently; after any failed
# step the tunnel is re-probed and the window abandoned if dead.
cd /root/repo || exit 1
LOG=runs/tpu_probe_r5.log

probe() {  # $1 = timeout; exit 0 when the tunnel answers with a tpu backend
  local out
  out=$(timeout "$1" python3 -c "import os,jax; p=os.environ.get('JAX_PLATFORMS'); p and jax.config.update('jax_platforms', p); print(jax.default_backend(), jax.devices()[0].device_kind)" 2>&1)
  [ $? -eq 0 ] && echo "$out" | grep -q tpu
}

bench_done() {  # $@ = partial keys; exit 0 when all tpu-tagged
  python3 - "$@" <<'EOF'
import json, sys
try:
    d = json.load(open("runs/bench_partial.json"))
except Exception:
    sys.exit(1)
ok = all(str(d.get(k, {}).get("host", "")).startswith("tpu")
         for k in sys.argv[1:])
sys.exit(0 if ok else 1)
EOF
}

flagship() {  # $1 dataset, $2 out dir, $3 rounds, $4 eval_every, $5 timeout, extra...
  local ds=$1 out=$2 rounds=$3 ev=$4 to=$5; shift 5
  echo "$(date -u +%FT%TZ) chip flagship $ds rounds=$rounds -> $out" >> "$LOG"
  timeout "$to" python3 -m fedml_tpu.experiments.flagship_scale \
    --dataset "$ds" --rounds "$rounds" --eval_every "$ev" \
    --drivers sim --eval_test_subsample 2000 --fused 50 "$@" --out "$out" \
    >> "runs/${out##*/}.log" 2>&1
  local rc=$?
  echo "$(date -u +%FT%TZ) chip flagship $ds exited rc=$rc" >> "$LOG"
  return $rc
}

all_done() {
  [ -f runs/flagship_femnist_tta_chip/summary.json ] || return 1
  [ -f runs/flagship_fedcifar100_tta_chip/summary.json ] || return 1
  [ -f runs/flagship_mnist_lr_tpu/summary.json ] || return 1
  bench_done fedavg_femnist_cnn fedavg_femnist_cnn_bf16 \
             fedavg_femnist_cnn_fused \
             fedavg_fused_rounds fedavg_fused_device_sampling \
             resnet18_gn_fedcifar100 transformer_flash_s2048 \
             fedavg_powerlaw_1000 federated_parallel_axes \
             time_to_target_mnist_lr time_to_target_acc || return 1
  return 0
}

window_over() {  # after a failed step: quick re-probe, abandon if dead
  if probe 30; then return 1; fi
  echo "$(date -u +%FT%TZ) tunnel dead on re-probe — window over" >> "$LOG"
  return 0
}

while true; do
  all_done && break
  ts=$(date -u +%FT%TZ)
  if probe 60; then
    echo "$ts probe LIVE — capture sequence starts (flagship TTA first)" >> "$LOG"
    while true; do  # single-pass step list; break = end of window
      if [ ! -f runs/flagship_femnist_tta_chip/summary.json ]; then
        flagship femnist_gen runs/flagship_femnist_tta_chip 1500 50 900 \
          || { window_over && break; }
      fi
      if [ ! -f runs/flagship_fedcifar100_tta_chip/summary.json ]; then
        flagship fed_cifar100_gen runs/flagship_fedcifar100_tta_chip 4000 200 1500 \
          || { window_over && break; }
      fi
      for step in 1 2 3; do
        bash runs/bench_stages_r5.sh "$step"
        echo "$(date -u +%FT%TZ) bench step $step exited rc=$?" >> "$LOG"
      done
      window_over && break
      if [ ! -f runs/flagship_mnist_lr_tpu/summary.json ]; then
        flagship mnist_gen runs/flagship_mnist_lr_tpu 200 10 600 \
          --batch_size 10 --lr 0.03 || { window_over && break; }
      fi
      if [ -x runs/extra_chip_r5.sh ]; then
        bash runs/extra_chip_r5.sh >> "$LOG" 2>&1
      fi
      break
    done
  else
    echo "$ts probe HUNG/DEAD" >> "$LOG"
  fi
  sleep 1200
done
echo "$(date -u +%FT%TZ) probe loop r5: ALL chip targets captured — exiting" >> "$LOG"
