"""Instrumented cross-silo chip probe: the full 10-silo ResNet-56 anchor
protocol at comm_round=2 with faulthandler stack dumps if any phase
stalls — diagnoses where the axon-tunnel cross-silo run wedges."""
import faulthandler
import logging
import os

faulthandler.dump_traceback_later(420, exit=True)

import jax  # noqa: E402
from fedml_tpu.algorithms.fedavg_cross_silo import run_fedavg_cross_silo  # noqa: E402
from fedml_tpu.data.cifar import load_partition_data_cifar  # noqa: E402
from fedml_tpu.models import create_model  # noqa: E402
from fedml_tpu.trainer.functional import TrainConfig  # noqa: E402

logging.basicConfig(level=logging.INFO)
ds = load_partition_data_cifar(
    "cifar10", os.path.expanduser("~/.cache/fedml_tpu_gen/cifar10_synth"),
    partition_method="hetero", partition_alpha=0.5, client_number=10)
model = create_model("resnet56", output_dim=10)
print("data+model ready; backend:", jax.default_backend(), flush=True)
final, hist, _ = run_fedavg_cross_silo(
    ds, model, worker_num=10, comm_round=2,
    train_cfg=TrainConfig(batch_size=64, lr=0.01, epochs=20))
print("DONE", hist, flush=True)
