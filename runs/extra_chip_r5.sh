#!/bin/bash
# Extra chip targets after the core capture sequence (read fresh by
# probe_loop_r5.sh each window, so this list is editable while the loop
# sleeps). Each step persists incrementally and tolerates a wedge.
cd /root/repo || exit 1

if [ ! -f runs/flagship_shakespeare_tta_chip/summary.json ]; then
  timeout 900 python3 -m fedml_tpu.experiments.flagship_scale \
    --dataset shakespeare_gen --rounds 800 --eval_every 25 \
    --drivers sim --eval_test_subsample 2000 --fused 25 \
    --batch_size 10 --lr 0.8 \
    --out runs/flagship_shakespeare_tta_chip \
    >> runs/flagship_shakespeare_tta_chip.log 2>&1
  echo "$(date -u +%FT%TZ) shakespeare chip flagship rc=$?"
fi

if [ ! -f runs/stackoverflow_nwp_stress_chip/summary.json ]; then
  timeout 600 python3 -m fedml_tpu.experiments.virtualization_stress \
    --dataset stackoverflow_nwp_gen --rounds 30 --eval_subsample 2000 \
    --out runs/stackoverflow_nwp_stress_chip \
    >> runs/stackoverflow_nwp_stress_chip.log 2>&1
  echo "$(date -u +%FT%TZ) nwp 342k-client stress on chip rc=$?"
fi
