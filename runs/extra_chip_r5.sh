#!/bin/bash
# Extra chip targets after the core capture sequence (read fresh by
# probe_loop_r5.sh each window, so this list is editable while the loop
# sleeps). Each step persists incrementally and tolerates a wedge.
cd /root/repo || exit 1

if [ ! -f runs/flagship_shakespeare_tta_chip/summary.json ]; then
  timeout 900 python3 -m fedml_tpu.experiments.flagship_scale \
    --dataset shakespeare_gen --rounds 800 --eval_every 25 \
    --drivers sim --eval_test_subsample 2000 --fused 25 \
    --batch_size 10 --lr 0.8 \
    --out runs/flagship_shakespeare_tta_chip \
    >> runs/flagship_shakespeare_tta_chip.log 2>&1
  echo "$(date -u +%FT%TZ) shakespeare chip flagship rc=$?"
fi

if [ ! -f runs/cross_silo_resnet56_chip/metrics.jsonl ]; then
  # the corpus is synthetic and cache-resident; regenerate if wiped
  [ -d "$HOME/.cache/fedml_tpu_gen/cifar10_synth" ] || \
    python3 runs/gen_cifar10_synth.py >> runs/cross_silo_resnet56_chip.log 2>&1
  # the cross-silo CIFAR10 anchor protocol at the FULL reference config
  # (benchmark/README.md:105): 10 silos, LDA alpha=0.5, E=20, B=64,
  # ResNet-56, 100 rounds. ~35 s/step on this host's CPU (8h) but ~2 ms
  # on chip — the whole 100-round protocol is minutes of device time.
  timeout 2000 python3 -m fedml_tpu.experiments.fed_launch \
    --algo fedavg_cross_silo --dataset cifar10 \
    --data_dir "$HOME/.cache/fedml_tpu_gen/cifar10_synth" \
    --model resnet56 --partition_method hetero --partition_alpha 0.5 \
    --client_num_in_total 10 --client_num_per_round 10 \
    --comm_round 100 --epochs 20 --batch_size 64 --lr 0.01 \
    --run_dir runs/cross_silo_resnet56_chip \
    >> runs/cross_silo_resnet56_chip.log 2>&1
  echo "$(date -u +%FT%TZ) cross-silo resnet56 anchor on chip rc=$?"
fi

if [ ! -f runs/stackoverflow_nwp_stress_chip/summary.json ]; then
  timeout 600 python3 -m fedml_tpu.experiments.virtualization_stress \
    --dataset stackoverflow_nwp_gen --rounds 30 --eval_subsample 2000 \
    --out runs/stackoverflow_nwp_stress_chip \
    >> runs/stackoverflow_nwp_stress_chip.log 2>&1
  echo "$(date -u +%FT%TZ) nwp 342k-client stress on chip rc=$?"
fi
