#!/bin/bash
# 20-min TPU probe cadence (VERDICT r3 #3). On a live window, capture in
# order of unique evidence value:
#   1. bench --stages=fused,fused_device   (the r3 #1 composed-lever contract)
#   2. femnist flagship at reference scale ON CHIP (1500-round TTA curve)
#   3. remaining bench stages (axes, tta rows)
#   4. fed_cifar100 + mnist flagships on chip
# Every step persists incrementally (bench_partial.json / *_history.jsonl —
# flagship_scale preserves partial history across retries), and steps are
# attempted independently each window: a step that keeps timing out cannot
# starve the ones after it. After any failed step the tunnel is re-probed
# and the window is abandoned if dead.
cd /root/repo || exit 1
LOG=runs/tpu_probe_r4.log

probe() {  # $1 = timeout; exit 0 when the tunnel answers with a tpu backend
  local out
  out=$(timeout "$1" python3 -c "import os,jax; p=os.environ.get('JAX_PLATFORMS'); p and jax.config.update('jax_platforms', p); print(jax.default_backend(), jax.devices()[0].device_kind)" 2>&1)
  [ $? -eq 0 ] && echo "$out" | grep -q tpu
}

bench_done() {  # $@ = partial keys; exit 0 when all tpu-tagged
  python3 - "$@" <<'EOF'
import json, sys
try:
    d = json.load(open("runs/bench_partial.json"))
except Exception:
    sys.exit(1)
ok = all(str(d.get(k, {}).get("host", "")).startswith("tpu")
         for k in sys.argv[1:])
sys.exit(0 if ok else 1)
EOF
}

bench_step() {  # $1 = --stages list
  FEDML_BENCH_TOTAL_TIMEOUT_S=900 timeout 1000 \
    python3 bench.py "--stages=$1" --resume-partial \
    >> runs/bench_r4_live.log 2>&1
  local rc=$?
  echo "$(date -u +%FT%TZ) bench --stages=$1 exited rc=$rc" >> "$LOG"
  return $rc
}

flagship() {  # $1 dataset, $2 out dir, $3 rounds, $4 eval_every, extra args...
  local ds=$1 out=$2 rounds=$3 ev=$4; shift 4
  echo "$(date -u +%FT%TZ) chip flagship $ds rounds=$rounds -> $out" >> "$LOG"
  timeout 540 python3 -m fedml_tpu.experiments.flagship_scale \
    --dataset "$ds" --rounds "$rounds" --eval_every "$ev" \
    --eval_test_subsample 10000 "$@" --out "$out" \
    >> "runs/${out##*/}.log" 2>&1
  local rc=$?
  echo "$(date -u +%FT%TZ) chip flagship $ds exited rc=$rc" >> "$LOG"
  return $rc
}

all_done() {
  bench_done fedavg_fused_rounds fedavg_fused_device_sampling \
             federated_parallel_axes time_to_target_mnist_lr \
             time_to_target_acc || return 1
  [ -f runs/flagship_femnist_tpu/summary.json ] || return 1
  [ -f runs/flagship_fedcifar100_tpu/summary.json ] || return 1
  [ -f runs/flagship_mnist_lr_tpu/summary.json ] || return 1
  return 0
}

window_over() {  # after a failed step: quick re-probe, abandon if dead
  if probe 30; then return 1; fi
  echo "$(date -u +%FT%TZ) tunnel dead on re-probe — window over" >> "$LOG"
  return 0
}

while true; do
  all_done && break
  ts=$(date -u +%FT%TZ)
  if probe 60; then
    echo "$ts probe LIVE — capture sequence starts" >> "$LOG"
    while true; do  # single-pass step list; break = end of window
      if ! bench_done fedavg_fused_rounds fedavg_fused_device_sampling; then
        bench_step fused,fused_device || { window_over && break; }
      fi
      if [ ! -f runs/flagship_femnist_tpu/summary.json ]; then
        flagship femnist_gen runs/flagship_femnist_tpu 1500 100 \
          || { window_over && break; }
      fi
      if ! bench_done federated_parallel_axes time_to_target_mnist_lr \
                      time_to_target_acc; then
        bench_step axes,tta_mnist,tta || { window_over && break; }
      fi
      if [ ! -f runs/flagship_fedcifar100_tpu/summary.json ]; then
        flagship fed_cifar100_gen runs/flagship_fedcifar100_tpu 4000 250 \
          || { window_over && break; }
      fi
      if [ ! -f runs/flagship_mnist_lr_tpu/summary.json ]; then
        flagship mnist_gen runs/flagship_mnist_lr_tpu 200 10 \
          --batch_size 10 --lr 0.03 || { window_over && break; }
      fi
      break
    done
  else
    echo "$ts probe HUNG/DEAD" >> "$LOG"
  fi
  sleep 1200
done
echo "$(date -u +%FT%TZ) probe loop: ALL chip targets captured — exiting" >> "$LOG"
