#!/bin/bash
# 20-min TPU probe cadence (VERDICT r3 #3). On a live window, immediately
# run ONLY the chip stages still missing (fused composition is the r3 #1
# contract number), merging next to already-captured rows.
cd /root/repo || exit 1
LOG=runs/tpu_probe_r4.log
TARGET_STAGES="fused,fused_device,axes,tta_mnist,tta"
while true; do
  # stop once every target stage carries a tpu host tag
  python3 - <<'EOF' && break
import json, sys
d = json.load(open("runs/bench_partial.json"))
keys = ["fedavg_fused_rounds", "fedavg_fused_device_sampling",
        "federated_parallel_axes", "time_to_target_mnist_lr",
        "time_to_target_acc"]
done = all(str(d.get(k, {}).get("host", "")).startswith("tpu") for k in keys)
sys.exit(0 if done else 1)
EOF
  ts=$(date -u +%FT%TZ)
  out=$(timeout 60 python3 -c "import os,jax; p=os.environ.get('JAX_PLATFORMS'); p and jax.config.update('jax_platforms', p); print(jax.default_backend(), jax.devices()[0].device_kind)" 2>&1)
  rc=$?
  if [ $rc -eq 0 ] && echo "$out" | grep -q tpu; then
    echo "$ts probe LIVE ($out) — running bench --stages=$TARGET_STAGES" >> "$LOG"
    FEDML_BENCH_TOTAL_TIMEOUT_S=1500 timeout 1800 \
      python3 bench.py "--stages=$TARGET_STAGES" --resume-partial \
      >> runs/bench_r4_live.log 2>&1
    echo "$(date -u +%FT%TZ) bench stage run exited rc=$?" >> "$LOG"
  else
    echo "$ts probe HUNG/DEAD rc=$rc (${out:0:80})" >> "$LOG"
  fi
  sleep 1200
done
echo "$(date -u +%FT%TZ) probe loop: all target stages chip-captured — exiting" >> "$LOG"
